"""Fleet trace assembly (`pio trace`), journal merge-tail
(`pio events`), and tail-based trace retention.

The acceptance e2e: a query->storage request served by TWO live HTTP
daemons (query server + storage RPC server) assembles into ONE span
tree via `pio trace` fanning out to both /traces.json surfaces. Plus:
clock-skew correction on constructed two-process spans, tail-ring
retention of a slow trace across main-ring churn, error-pinning at the
transport, `pio events` incremental merge, and CLI exit codes.
"""

import io
import json
import urllib.request

import pytest

from predictionio_tpu.common import journal, telemetry, tracing, traceview
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api.http import (
    dispatch_request, serve_background,
)
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams,
)
from predictionio_tpu.models.recommendation.als_algorithm import ALSAlgorithm
from predictionio_tpu.tools.cli import main as cli_main
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


@pytest.fixture(autouse=True)
def _clean():
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()
    journal.set_enabled(None)
    journal.clear()
    yield
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()
    journal.set_enabled(None)
    journal.clear()


# ---------------------------------------------------------------------------
# clock-skew correction + tree rendering (constructed spans)
# ---------------------------------------------------------------------------

def _span(sid, pid, name, service, start, dur, target):
    return {"spanId": sid, "parentId": pid, "name": name,
            "service": service, "startMs": start, "durationMs": dur,
            "target": target}


def test_skew_correction_centers_server_inside_client():
    """Process B's clock is 5 s ahead; after correction its spans sit
    centered inside their client parents, and B's OTHER spans shift by
    the same offset."""
    spans = [
        _span("a", None, "server:/queries.json", "QueryAPI",
              1000.0, 10.0, "A"),
        _span("b", "a", "storage", "rpc", 1002.0, 6.0, "A"),
        _span("c", "b", "server:/rpc", "StorageRPCAPI",
              6003.0, 4.0, "B"),
        _span("d", "c", "disk", "StorageRPCAPI", 6004.0, 2.0, "B"),
    ]
    offsets = traceview.correct_skew(spans)
    assert offsets["A"] == 0.0
    assert offsets["B"] == pytest.approx(-5000.0)
    by = {s["spanId"]: s for s in spans}
    # c centered inside b: 1002 + (6-4)/2 = 1003
    assert by["c"]["startMs"] == pytest.approx(1003.0)
    assert by["d"]["startMs"] == pytest.approx(1004.0)


def test_skew_correction_single_process_is_identity():
    spans = [
        _span("a", None, "root", "X", 100.0, 5.0, "A"),
        _span("b", "a", "child", "X", 101.0, 2.0, "A"),
    ]
    offsets = traceview.correct_skew(spans)
    assert offsets == {"A": 0.0}
    assert spans[0]["startMs"] == 100.0


def test_render_tree_shape():
    spans = [
        _span("a", None, "root", "QueryAPI", 0.0, 10.0, "A"),
        _span("b", "a", "child1", "QueryAPI", 1.0, 3.0, "A"),
        _span("c", "b", "grandchild", "Other", 2.0, 1.0, "B"),
        _span("d", "a", "child2", "QueryAPI", 5.0, 4.0, "A"),
    ]
    text = traceview.render_tree("cafe1234", spans, pinned=["slow"])
    lines = text.splitlines()
    assert "cafe1234" in lines[0] and "[pinned: slow]" in lines[0]
    assert "4 span(s)" in lines[0] and "2 target(s)" in lines[0]
    # tree order: root, child1, grandchild (deeper indent), child2
    assert [ln.split("ms")[1].strip().split()[0] for ln in lines[1:]] \
        == ["root", "+-", "+-", "+-"]
    assert "grandchild" in lines[3]
    assert lines[3].index("+-") > lines[2].index("+-")   # deeper
    for ln in lines[1:]:
        assert "|" in ln and "#" in ln                   # the bar


def test_children_sorted_and_roots_detected():
    spans = [
        _span("b", "a", "late", "X", 9.0, 1.0, "A"),     # parent absent
        _span("c", "b", "k2", "X", 5.0, 1.0, "A"),
        _span("d", "b", "k1", "X", 3.0, 1.0, "A"),
    ]
    roots, children = traceview._children_index(spans)
    assert [r["spanId"] for r in roots] == ["b"]         # orphan = root
    assert [c["name"] for c in children["b"]] == ["k1", "k2"]


# ---------------------------------------------------------------------------
# tail retention: the slow trace survives main-ring churn
# ---------------------------------------------------------------------------

def test_tail_retention_keeps_slow_trace_through_churn(monkeypatch):
    """A constructed slow trace stays resolvable via ?trace_id= after
    the main ring (PIO_TRACE_BUFFER spans) churns past capacity."""
    monkeypatch.setenv("PIO_TRACE_TAIL_MS", "1.0")
    tracing.set_enabled(True)
    slow_ctx = tracing.new_context()
    with tracing.activate(slow_ctx):
        tracing.record_span("slow_op", tracing.current(), 0.050,
                            service="test")
    assert tracing.tail_retained() >= 1
    # churn: far more healthy spans than the main ring holds
    monkeypatch.setenv("PIO_TRACE_TAIL_MS", "1e9")
    for k in range(tracing._ring.capacity + 64):
        with tracing.activate(tracing.new_context()):
            tracing.record_span("healthy", tracing.current(), 0.0001)
    # the slow trace's spans are GONE from the main ring...
    main_only = [s for s in tracing._ring.spans()
                 if s.trace_id == slow_ctx.trace_id]
    assert not main_only
    # ...but the targeted read still resolves it, flagged as pinned
    snap = tracing.snapshot(trace_id=slow_ctx.trace_id)
    assert len(snap["traces"]) == 1
    trace = snap["traces"][0]
    assert trace["traceId"] == slow_ctx.trace_id
    assert any(s["name"] == "slow_op" for s in trace["spans"])
    assert "slow" in trace["pinned"]
    assert snap["tail"]["retained"] >= 1


def test_tail_ring_bounded_oldest_pin_evicted(monkeypatch):
    monkeypatch.setenv("PIO_TRACE_TAIL_TRACES", "4")
    tracing.set_enabled(True)
    ids = []
    for k in range(8):
        ctx = tracing.new_context()
        ids.append(ctx.trace_id)
        tracing.pin_trace(ctx.trace_id, "slow")
    assert tracing.tail_retained() == 4
    for old in ids[:4]:
        assert not tracing._tail.reasons_for(old)
    for new in ids[4:]:
        assert tracing._tail.reasons_for(new)


def test_error_response_pins_trace():
    """A 5xx on a traced request pins the trace at the transport."""
    class Boom:
        def handle(self, method, path, query=None, body=b"",
                   headers=None):
            raise RuntimeError("kaboom")

    out = dispatch_request(Boom(), "GET", "/explode", b"",
                           {"x-pio-trace": "feedface00000001-aaaa"})
    assert out.status == 500
    assert "error" in tracing._tail.reasons_for("feedface00000001")


def test_degraded_response_pins_trace(memory_storage):
    from journal_test_util import trained_query_api
    from predictionio_tpu.common import resilience

    api = trained_query_api(memory_storage, batching="off")
    try:
        algo = api.algorithms[0]
        real = type(algo).predict

        def tainted(model, query):
            resilience.note_degraded("test lookup failure")
            return real(algo, model, query)

        algo.predict = tainted
        server, port = serve_background(api)
        try:
            tracing.set_enabled(True)
            req = urllib.request.Request(
                f"http://localhost:{port}/queries.json",
                data=json.dumps({"user": "u1", "num": 2}).encode(),
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req) as r:
                body = json.loads(r.read())
            assert body.get("degraded") is True
            reasons = []
            with tracing._tail._lock:
                for entry in tracing._tail._traces.values():
                    reasons.extend(entry["reasons"])
            assert "degraded" in reasons
        finally:
            server.shutdown()
    finally:
        api.close()


# ---------------------------------------------------------------------------
# the acceptance e2e: one tree from two live daemons
# ---------------------------------------------------------------------------

class _LookupALS(ALSAlgorithm):
    """ALS whose batched predict does one live storage lookup, so the
    trace genuinely crosses into the storage daemon."""

    def predict_batch(self, model, queries):
        self._serving_storage.get_meta_data_apps().get_all()
        return super().predict_batch(model, queries)

    def bind_serving(self, ctx) -> None:
        self._serving_storage = ctx.storage


def _lookup_engine():
    from predictionio_tpu.controller import Engine, FirstServing
    from predictionio_tpu.models.recommendation.data_source import (
        DataSource,
    )
    from predictionio_tpu.models.recommendation.preparator import Preparator
    return Engine(data_source_class=DataSource,
                  preparator_class=Preparator,
                  algorithm_class_map={"als": _LookupALS},
                  serving_class=FirstServing)


def _two_daemon_fleet():
    """(query_api, query server, query url, rpc server, rpc url)."""
    from predictionio_tpu.data.storage.remote import serve_storage

    backing = Storage(env={
        "PIO_STORAGE_SOURCES_B_TYPE": "memory",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "B",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "B",
    })
    engine = _lookup_engine()
    apps = backing.get_meta_data_apps()
    app_id = apps.insert(App(0, "FleetApp", None))
    backing.get_events().init(app_id)
    import datetime as dt
    backing.get_events().insert_batch([
        Event(event="rate", entity_type="user", entity_id=f"u{u}",
              target_entity_type="item", target_entity_id=f"i{i}",
              properties=DataMap({"rating": float(1 + (u + i) % 5)}),
              event_time=dt.datetime(2021, 1, 1, tzinfo=dt.timezone.utc))
        for u in range(6) for i in range(5)], app_id)
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="FleetApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=3, numIterations=2,
                                       lambda_=0.05, seed=1)),))
    run_train(WorkflowContext(storage=backing), engine, ep,
              engine_factory="fleet-test",
              params_json={
                  "datasource": {"params": {"appName": "FleetApp"}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 3, "numIterations": 2, "lambda": 0.05,
                      "seed": 1}}]})
    rpc_server = serve_storage(backing, host="127.0.0.1", port=0)
    rpc_port = rpc_server.server_address[1]
    remote = Storage(env={
        "PIO_STORAGE_SOURCES_R_TYPE": "remote",
        "PIO_STORAGE_SOURCES_R_URL": f"http://127.0.0.1:{rpc_port}",
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "R",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "R",
    })
    api = QueryAPI(storage=remote, engine=engine,
                   config=ServerConfig(batching="on"))
    server, port = serve_background(api)
    return (api, server, f"http://localhost:{port}",
            rpc_server, f"http://127.0.0.1:{rpc_port}")


def test_pio_trace_assembles_one_tree_from_two_live_daemons():
    """THE acceptance e2e: a query->storage request's spans, read back
    from TWO live daemons over HTTP, join into ONE tree containing
    both services, and `pio trace` renders it (exit 0)."""
    api, server, query_url, rpc_server, rpc_url = _two_daemon_fleet()
    tracing.clear()
    tracing.set_enabled(True)
    try:
        req = urllib.request.Request(
            f"{query_url}/queries.json",
            data=json.dumps({"user": "u1", "num": 3}).encode(),
            headers={"Content-Type": "application/json"}, method="POST")
        with urllib.request.urlopen(req) as r:
            assert r.status == 200
        # the trace that carried the query (it has a storage RPC span)
        snap = tracing.snapshot()
        trace_id = None
        for trace in snap["traces"]:
            if any(s["name"] == "server:/rpc" for s in trace["spans"]):
                trace_id = trace["traceId"]
                break
        assert trace_id is not None, snap
        targets = [query_url, rpc_url]
        spans, errors, _pinned = traceview.fetch_trace(targets, trace_id)
        assert not errors
        traceview.correct_skew(spans)
        roots, children = traceview._children_index(spans)
        assert len(roots) == 1, [
            (s["name"], s["parentId"]) for s in spans]   # ONE tree
        names = {s["name"] for s in spans}
        for expected in ("server:/queries.json", "admission",
                         "dispatch", "storage", "server:/rpc"):
            assert expected in names, sorted(names)
        services = {s["service"] for s in spans}
        assert "StorageRPCAPI" in services       # the storage daemon's
        assert "query-server" in services or "QueryAPI" in services
        # the CLI end of it: renders and exits 0
        buf = io.StringIO()
        rc = traceview.run_trace(trace_id, targets, out=buf)
        text = buf.getvalue()
        assert rc == 0, text
        assert "server:/queries.json" in text
        assert "server:/rpc" in text
        # unknown trace id -> 1
        buf = io.StringIO()
        assert traceview.run_trace("0" * 16, targets, out=buf) == 1
    finally:
        tracing.set_enabled(None)
        server.shutdown()
        api.close()
        rpc_server.shutdown()
        rpc_server.server_close()


def test_pio_events_merges_and_follows_fleet_journals():
    api, server, query_url, rpc_server, rpc_url = _two_daemon_fleet()
    try:
        journal.clear()
        journal.emit("breaker", "opened for ep", level=journal.RED,
                     endpoint="ep")
        journal.emit("wal", "repaired torn tail", level=journal.WARN)
        targets = [query_url, rpc_url]
        buf = io.StringIO()
        rc = traceview.run_events(targets, level="warn", out=buf)
        text = buf.getvalue()
        assert rc == 0
        assert "breaker" in text and "wal" in text
        assert "RED" in text and "WARN" in text
        # incremental: from the last seq, a fresh read is empty...
        last = journal.snapshot()["lastSeq"]
        buf = io.StringIO()
        assert traceview.run_events(targets, since_seq=last,
                                    out=buf) == 0
        assert buf.getvalue() == ""
        # ...and --follow picks up what lands between polls
        journal.emit("lifecycle", "gen 2 live")
        buf = io.StringIO()
        rc = traceview.run_events(targets, since_seq=last, follow=True,
                                  interval_s=0.01, out=buf, max_polls=2)
        assert rc == 0 and "gen 2 live" in buf.getvalue()
    finally:
        server.shutdown()
        api.close()
        rpc_server.shutdown()
        rpc_server.server_close()


# ---------------------------------------------------------------------------
# CLI plumbing + doctor line
# ---------------------------------------------------------------------------

def test_cli_trace_and_events_exit_codes():
    # both targets dead -> 2
    assert cli_main(["trace", "a" * 16,
                     "--targets", "http://127.0.0.1:9",
                     "--timeout", "0.3"]) == 2
    assert cli_main(["events",
                     "--targets", "http://127.0.0.1:9",
                     "--timeout", "0.3"]) == 2
    # --targets is required and must be non-empty
    assert cli_main(["trace", "a" * 16, "--targets", " "]) == 1


def test_doctor_recent_events_line(memory_storage):
    from predictionio_tpu.data.api import EventAPI
    from predictionio_tpu.tools import doctor

    api = EventAPI(storage=memory_storage)
    server, port = serve_background(api)
    try:
        journal.clear()
        buf = io.StringIO()
        doctor.run_doctor(f"http://localhost:{port}", out=buf)
        assert "events" in buf.getvalue()
        assert "no WARN/RED journal events" in buf.getvalue()
        journal.emit("wal", "repaired torn WAL tail",
                     level=journal.WARN, path="x")
        buf = io.StringIO()
        doctor.run_doctor(f"http://localhost:{port}", out=buf)
        text = buf.getvalue()
        assert "repaired torn WAL tail" in text
        assert "ago)" in text
        # journal off -> the NA hint, not a crash
        journal.set_enabled(False)
        buf = io.StringIO()
        doctor.run_doctor(f"http://localhost:{port}", out=buf)
        assert "journal off" in buf.getvalue()
    finally:
        server.shutdown()


if __name__ == "__main__":
    pytest.main([__file__, "-q"])
