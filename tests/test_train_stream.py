"""Out-of-core streaming training (ISSUE 14).

The contracts under test:

- the synthetic generator is DETERMINISTIC and re-iterable (chunk c is a
  pure function of (seed, c));
- streamed training (PIO_TRAIN_STREAM=on) produces BIT-IDENTICAL factor
  matrices to the in-core path, from the library surface AND through the
  full `pio train` front door over a real event store;
- the streamed TrainingData holds NO host COO (the O(chunk) host claim's
  structural half) and the big-layout cache still recognizes an
  unchanged dataset via the stream digest;
- PIO_TRAIN_STREAM=off is an exact revert (host arrays retained,
  identical factors);
- the streamed sharded assembly (als_dist.shard_staged_coo) matches the
  host-assembled sharded layout bitwise at one device and trains finite
  factors on the 8-device mesh;
- the 1 B-rating soak (slow-marked, PIO_SOAK_RATINGS overrides the
  count) trains to completion with the peak PIPELINE host RSS — RSS
  minus live jax array bytes, the honest reading on CPU backends where
  device buffers share the RSS (KNOWN_ISSUES #14) — under the 4 GB
  O(chunk) budget.
"""

import os

import numpy as np
import pytest

from predictionio_tpu.data import store, synthetic
from predictionio_tpu.data.storage import App, Storage
from predictionio_tpu.models.recommendation.als_algorithm import (
    ALSAlgorithm, ALSAlgorithmParams,
)
from predictionio_tpu.models.recommendation import als_algorithm


@pytest.fixture(autouse=True)
def _fresh_caches(monkeypatch):
    """Layout caches are process-wide; every test starts cold so hits
    and builds are attributable. The read-pipeline env is cleared too:
    streaming resolution depends on staging availability, and a leaked
    PIO_READ_STAGE=0 from an unrelated test would silently flip every
    contract here to the in-core path."""
    monkeypatch.setattr(als_algorithm, "_BIG_LAYOUT_CACHE", [])
    for k in ("PIO_TRAIN_STREAM", "PIO_SYNTHETIC_EVENTS",
              "PIO_SYNTHETIC_SEED", "PIO_READ_STAGE", "PIO_READ_OVERLAP",
              "PIO_READ_THREADS"):
        monkeypatch.delenv(k, raising=False)
    yield


def _prepared(td):
    return type("P", (), {"ratings": td})()


# ---------------------------------------------------------------------------
# synthetic generator
# ---------------------------------------------------------------------------

def test_synthetic_deterministic_and_reiterable():
    src = synthetic.chunk_source(5000, seed=11, chunk=700)
    a = list(src.chunks())
    b = list(src.chunks())           # second epoch: byte-identical
    assert len(a) == 8 and len(b) == 8
    for ca, cb in zip(a, b):
        for k in ("entity_code", "target_code", "event_code", "rating",
                  "time_ms"):
            assert ca[k].tobytes() == cb[k].tobytes()
    # chunk c is addressable independently (per-epoch re-scans need no
    # state): regenerating chunk 3 alone matches the full pass
    u, i, r = src.chunk_codes(3)
    assert (a[3]["rating"] == r).all()
    assert (a[3]["entity_code"] - 3 == u).all()
    # a different seed is a different dataset
    other = synthetic.chunk_source(5000, seed=12, chunk=700)
    assert next(other.chunks())["rating"].tobytes() != \
        a[0]["rating"].tobytes()
    # total rows = n_events, ids in range
    n = sum(c["rating"].shape[0] for c in a)
    assert n == 5000
    cfg = src.cfg
    assert (u >= 0).all() and (u < cfg.n_users).all()


def test_synthetic_zipf_skew():
    src = synthetic.chunk_source(20_000, seed=1, n_items=64, chunk=4096)
    counts = np.zeros(64, np.int64)
    for ch in src.chunks():
        counts += np.bincount(ch["target_code"] - 3 - src.cfg.n_users,
                              minlength=64)
    # power-law popularity: the head item dominates the median item
    assert counts[0] > 8 * max(np.median(counts), 1)


# ---------------------------------------------------------------------------
# streamed vs in-core: the bit-parity contract (library surface)
# ---------------------------------------------------------------------------

def test_streamed_training_bit_identical_to_incore():
    td_s = synthetic.training_data(4000, seed=5, chunk=600, stream=True)
    td_i = synthetic.training_data(4000, seed=5, chunk=600, stream=False)
    # structural half of the O(chunk) claim: no host COO exists
    assert td_s.streamed and td_s.user_idx is None and td_s.rating is None
    assert td_s._stream_digest and td_s.n == td_i.n
    assert not td_i.streamed
    # identical vocabs (dictionary-code order either way)
    assert td_s.user_vocab.to_dict() == td_i.user_vocab.to_dict()
    assert td_s.item_vocab.to_dict() == td_i.item_vocab.to_dict()
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=3, numIterations=2, seed=7))
    m_s = algo.train(None, _prepared(td_s))
    m_i = algo.train(None, _prepared(td_i))
    np.testing.assert_array_equal(np.asarray(m_s.user_factors),
                                  np.asarray(m_i.user_factors))
    np.testing.assert_array_equal(np.asarray(m_s.item_factors),
                                  np.asarray(m_i.item_factors))
    # the staged buffers were consumed by the layout (donated off-CPU)
    assert td_s._staged_coo is None


def test_streamed_layout_cache_hits_via_digest(monkeypatch):
    """A repeat streamed train over an unchanged dataset reuses the
    process-wide layout through the stream digest (the content
    fingerprint of a dataset whose host copy never existed)."""
    monkeypatch.setenv("PIO_ALS_BIG_LAYOUT_MIN", "1")   # force big tier
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=2, numIterations=1, seed=3))
    td1 = synthetic.training_data(2000, seed=9, chunk=512, stream=True)
    h0, b0 = (als_algorithm.LAYOUT_STATS["hits"],
              als_algorithm.LAYOUT_STATS["builds"])
    algo.train(None, _prepared(td1))
    td2 = synthetic.training_data(2000, seed=9, chunk=512, stream=True)
    algo.train(None, _prepared(td2))
    assert als_algorithm.LAYOUT_STATS["builds"] - b0 == 1
    assert als_algorithm.LAYOUT_STATS["hits"] - h0 == 1
    # the fingerprint is MODE-AGNOSTIC (raw chunk digest): an in-core
    # retrain of the same dataset hits the streamed train's entry too
    td_ic = synthetic.training_data(2000, seed=9, chunk=512, stream=False)
    algo.train(None, _prepared(td_ic))
    assert als_algorithm.LAYOUT_STATS["hits"] - h0 == 2
    assert als_algorithm.LAYOUT_STATS["builds"] - b0 == 1
    # a changed dataset can never hit (different digest)
    td3 = synthetic.training_data(2000, seed=10, chunk=512, stream=True)
    algo.train(None, _prepared(td3))
    assert als_algorithm.LAYOUT_STATS["builds"] - b0 == 2


def test_streamed_missing_rating_raises_same_error():
    """The missing-rating check runs on device in stream mode but keeps
    the in-core path's error contract."""
    src = synthetic.chunk_source(300, seed=2, chunk=128)

    def poisoned():
        for ch in src.chunks():
            ch = dict(ch)
            r = ch["rating"].copy()
            r[::7] = np.nan
            ch["rating"] = r
            yield ch

    col = store.columnar_from_stream(
        src.pool(), poisoned(), event_names=["rate", "buy"], stream=True)
    assert col.entity_idx is None    # genuinely streamed
    from predictionio_tpu.models.recommendation.data_source import (
        training_data_from_columnar,
    )
    with pytest.raises(ValueError, match="have no numeric 'rating'"):
        training_data_from_columnar(col)


def test_stream_mode_resolution(monkeypatch):
    assert store.train_stream_mode() == "auto"
    monkeypatch.setenv("PIO_TRAIN_STREAM", "off")
    assert store.train_stream_mode() == "off"
    assert not store.resolve_train_stream()
    assert not als_algorithm.stream_wanted()
    monkeypatch.setenv("PIO_TRAIN_STREAM", "on")
    assert store.resolve_train_stream()
    assert als_algorithm.stream_wanted()
    # `on` streams even with a warm layout cache (digest-keyed lookup)
    monkeypatch.setattr(als_algorithm, "_BIG_LAYOUT_CACHE",
                        [("meta", b"crc", object())])
    assert als_algorithm.stream_wanted()
    # `auto` declines the warm retrain, exactly like staging_wanted
    monkeypatch.setenv("PIO_TRAIN_STREAM", "auto")
    assert not als_algorithm.stream_wanted()
    monkeypatch.setattr(als_algorithm, "_BIG_LAYOUT_CACHE", [])
    assert als_algorithm.stream_wanted()
    # no staging, no streaming (the columns must live somewhere)
    monkeypatch.setenv("PIO_READ_STAGE", "0")
    assert not als_algorithm.stream_wanted()
    monkeypatch.setenv("PIO_TRAIN_STREAM", "on")
    assert not store.resolve_train_stream()


# ---------------------------------------------------------------------------
# the full front door: event store -> `pio train` streamed vs in-core
# ---------------------------------------------------------------------------

def _el_storage(tmp_path):
    s = Storage(env={
        "PIO_STORAGE_SOURCES_M_TYPE": "memory",
        "PIO_STORAGE_SOURCES_EL_TYPE": "eventlog",
        "PIO_STORAGE_SOURCES_EL_PATH": str(tmp_path / "el"),
        "PIO_STORAGE_REPOSITORIES_METADATA_SOURCE": "M",
        "PIO_STORAGE_REPOSITORIES_EVENTDATA_SOURCE": "EL",
        "PIO_STORAGE_REPOSITORIES_MODELDATA_SOURCE": "M",
    })
    app_id = s.get_meta_data_apps().insert(App(0, "StreamApp"))
    s.get_events().init(app_id)
    return s, app_id


def _train_front_door(storage, mode, monkeypatch, seed=13):
    from predictionio_tpu.controller.engine import EngineParams
    from predictionio_tpu.models.recommendation import (
        ALSAlgorithmParams as AP, DataSourceParams,
        RecommendationEngine,
    )
    from predictionio_tpu.workflow import run_train
    from predictionio_tpu.workflow.context import WorkflowContext
    from predictionio_tpu.workflow import model_io

    monkeypatch.setenv("PIO_TRAIN_STREAM", mode)
    als_algorithm._BIG_LAYOUT_CACHE.clear()
    engine = RecommendationEngine()
    ctx = WorkflowContext(storage=storage)
    iid = run_train(
        ctx, engine,
        EngineParams(
            data_source_params=DataSourceParams(appName="StreamApp"),
            algorithm_params_list=(("als", AP(
                rank=3, numIterations=2, seed=seed)),)),
        engine_factory="stream-test")
    row = storage.get_meta_data_engine_instances().get(iid)
    blob = storage.get_model_data_models().get(iid).models
    models = model_io.deserialize_models(blob)
    return row, models


def test_front_door_streamed_equals_incore(tmp_path, monkeypatch):
    storage, app_id = _el_storage(tmp_path)
    src = synthetic.chunk_source(3000, seed=21, chunk=512)
    synthetic.write_events(src, storage, app_id)
    row_off, models_off = _train_front_door(storage, "off", monkeypatch)
    row_on, models_on = _train_front_door(storage, "on", monkeypatch)
    assert row_off.runtime_conf.get("train_stream") == "off"
    assert row_on.runtime_conf.get("train_stream") == "on"
    m_off, m_on = models_off[0], models_on[0]
    np.testing.assert_array_equal(np.asarray(m_off.user_factors),
                                  np.asarray(m_on.user_factors))
    np.testing.assert_array_equal(np.asarray(m_off.item_factors),
                                  np.asarray(m_on.item_factors))
    assert m_off.user_vocab.to_dict() == m_on.user_vocab.to_dict()


def test_write_events_streams_bounded_batches(memory_storage):
    """The Event-object fallback of write_events (backends without the
    bulk columnar append) streams bounded insert_batch calls instead of
    materializing a whole chunk of Event objects in-core — the PR 14
    ROADMAP follow-up. Structural half: no insert ever exceeds the
    batch bound even when the chunk is much larger. RSS half: the
    process high-water mark moves by at most a modest constant while
    writing, not by O(chunk) of Event objects."""
    from predictionio_tpu.common.devicewatch import host_memory_stats
    from predictionio_tpu.data.storage import App

    app_id = memory_storage.get_meta_data_apps().insert(App(0, "SynIngest"))
    ev = memory_storage.get_events()
    assert not hasattr(ev, "append_encoded")   # the fallback path
    seen = []
    real_insert = ev.insert_batch

    def counting_insert(events, app, channel=None):
        seen.append(len(events))
        return real_insert(events, app, channel)

    ev.insert_batch = counting_insert
    try:
        src = synthetic.chunk_source(20_000, seed=5, chunk=1 << 14)
        before = host_memory_stats().get("peakRssBytes")
        total = synthetic.write_events(src, memory_storage, app_id,
                                       batch=1024)
        after = host_memory_stats().get("peakRssBytes")
    finally:
        ev.insert_batch = real_insert
    assert total == 20_000
    assert sum(seen) == 20_000
    # the chunk (16384 events) never materializes at once: every insert
    # is at most the batch bound
    assert max(seen) <= 1024
    if before is not None and after is not None:
        # generous ceiling — the stored events themselves are O(N), but
        # a whole-chunk Event materialization would add hundreds of MB
        assert after - before < 200 * 2**20, (before, after)


def test_synthetic_cli_flags(monkeypatch):
    from predictionio_tpu.tools.cli import _apply_read_env, build_parser

    args = build_parser().parse_args(
        ["train", "--synthetic", "5000", "--synthetic-seed", "9",
         "--stream", "on"])
    # register the keys with monkeypatch BEFORE the direct writes so
    # teardown restores the pre-test state (see test_cli_read_flags)
    for k in ("PIO_SYNTHETIC_EVENTS", "PIO_SYNTHETIC_SEED",
              "PIO_TRAIN_STREAM"):
        monkeypatch.setenv(k, "pre")
    _apply_read_env(args)
    assert os.environ["PIO_SYNTHETIC_EVENTS"] == "5000"
    assert os.environ["PIO_SYNTHETIC_SEED"] == "9"
    assert os.environ["PIO_TRAIN_STREAM"] == "on"
    cfg = synthetic.env_config()
    assert cfg is not None and cfg.n_events == 5000 and cfg.seed == 9
    for k in ("PIO_SYNTHETIC_EVENTS", "PIO_SYNTHETIC_SEED",
              "PIO_TRAIN_STREAM"):
        monkeypatch.delenv(k, raising=False)
    assert synthetic.env_config() is None


def test_synthetic_datasource_interception(monkeypatch):
    """`pio train --synthetic N`: the recommendation DataSource trains
    on the generator without touching any event store."""
    from predictionio_tpu.models.recommendation.data_source import (
        DataSource, DataSourceParams,
    )
    monkeypatch.setenv("PIO_SYNTHETIC_EVENTS", "1200")
    monkeypatch.setenv("PIO_SYNTHETIC_SEED", "4")
    ds = DataSource(DataSourceParams(appName="NoSuchApp"))
    td = ds.read_training(ctx=None)   # no storage needed at all
    assert td.n == 1200
    ref = synthetic.training_data(1200, seed=4)
    assert len(td.user_vocab) == len(ref.user_vocab)


# ---------------------------------------------------------------------------
# streamed sharded assembly (parallel/als_dist.py)
# ---------------------------------------------------------------------------

def test_shard_staged_coo_matches_host_layout_at_one_device():
    from predictionio_tpu.ops import als
    from predictionio_tpu.parallel import als_dist
    from predictionio_tpu.parallel.mesh import get_mesh

    td_s = synthetic.training_data(2500, seed=6, chunk=400, stream=True)
    td_i = synthetic.training_data(2500, seed=6, chunk=400, stream=False)
    mesh = get_mesh(1)
    u, i, r = td_s._staged_coo
    pre = als_dist.shard_staged_coo(
        mesh, u, i, r, n_users=len(td_s.user_vocab),
        n_items=len(td_s.item_vocab))
    U_s, V_s = als_dist.train_explicit_sharded(
        mesh, pre, rank=3, iterations=2, seed=9, kernel="csrb")
    data_h = als.prepare_ratings(
        td_i.user_idx, td_i.item_idx, td_i.rating,
        n_users=len(td_i.user_vocab), n_items=len(td_i.item_vocab))
    U_h, V_h = als_dist.train_explicit_sharded(
        mesh, data_h, rank=3, iterations=2, seed=9, kernel="csrb")
    np.testing.assert_array_equal(np.asarray(U_s), np.asarray(U_h))
    np.testing.assert_array_equal(np.asarray(V_s), np.asarray(V_h))


def test_shard_staged_coo_trains_on_mesh():
    import jax

    from predictionio_tpu.parallel import als_dist
    from predictionio_tpu.parallel.mesh import get_mesh

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest XLA_FLAGS)")
    td = synthetic.training_data(4000, seed=5, chunk=600, stream=True)
    mesh = get_mesh(8)
    u, i, r = td._staged_coo
    pre = als_dist.shard_staged_coo(
        mesh, u, i, r, n_users=len(td.user_vocab),
        n_items=len(td.item_vocab), route_rows=512)
    # every rating routed exactly once, per-device row blocks contiguous
    assert int(pre.su.nnz_per_dev.sum()) == td.n
    assert int(pre.si.nnz_per_dev.sum()) == td.n
    U, V = als_dist.train_explicit_sharded(
        mesh, pre, rank=3, iterations=2, seed=9, kernel="csrb")
    U, V = np.asarray(U), np.asarray(V)
    assert U.shape == (len(td.user_vocab), 3)
    assert V.shape == (len(td.item_vocab), 3)
    assert np.isfinite(U).all() and np.isfinite(V).all()


def test_streamed_mesh_train_through_algorithm(monkeypatch):
    """ALSAlgorithm.train with a mesh ctx consumes a streamed
    TrainingData through the sharded assembly (no host COO ever)."""
    import jax

    from predictionio_tpu.parallel.mesh import get_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs >= 2 CPU devices")
    td = synthetic.training_data(2000, seed=8, chunk=512, stream=True)
    assert td.streamed
    ctx = type("Ctx", (), {"mesh": get_mesh(2), "checkpoint_dir": None})()
    algo = ALSAlgorithm(ALSAlgorithmParams(rank=2, numIterations=1, seed=5))
    model = algo.train(ctx, _prepared(td))
    U = np.asarray(model.user_factors)
    assert U.shape == (len(td.user_vocab), 2) and np.isfinite(U).all()


# ---------------------------------------------------------------------------
# host-RSS observability (common/devicewatch.py)
# ---------------------------------------------------------------------------

def test_host_memory_stats_and_watcher():
    from predictionio_tpu.common import devicewatch

    st = devicewatch.host_memory_stats()
    # the dev/test container is Linux: the gauge must be live there
    assert st["rssBytes"] is None or st["rssBytes"] > 0
    if st["rssBytes"] is None:
        pytest.skip("/proc unavailable on this platform")
    assert st["peakRssBytes"] >= st["rssBytes"] * 0 and \
        st["memTotalBytes"] > 0
    with devicewatch.RssWatcher(interval_s=0.01) as w:
        ballast = np.ones(4 << 20, np.uint8)   # 4 MB of host pressure
        ballast[::4096] = 2
        import time
        time.sleep(0.05)
    assert w.samples > 0 and w.peak_rss > 0
    assert w.peak_pipeline <= w.peak_rss
    del ballast


def test_host_rss_in_debug_snapshot(monkeypatch):
    from predictionio_tpu.common import devicewatch

    monkeypatch.setenv("PIO_TELEMETRY", "1")
    snap = devicewatch.debug_snapshot()
    assert "hostMemory" in snap
    lines = devicewatch._collector.collect()
    text = "\n".join(lines)
    if devicewatch.host_rss_bytes() is not None:
        assert "pio_host_rss_bytes" in text
        assert "pio_host_rss_peak_bytes" in text


# ---------------------------------------------------------------------------
# the scale soak (slow; kept out of tier-1) + its tier-1-scale smoke
# ---------------------------------------------------------------------------

def _soak(n_events: int, budget_bytes: int, relative: bool = False):
    """``relative=True`` bounds the pipeline's GROWTH over the run
    (peak minus the watcher's first sample) instead of the absolute
    process footprint — the tier-1 smoke shares one long-lived pytest
    process whose baseline heap grows with every test added to the
    suite, which is suite length, not pipeline memory. The 1 B soak
    keeps the absolute bound: it runs deliberately, in a process whose
    RSS the pipeline dominates."""
    from predictionio_tpu.common import devicewatch
    from predictionio_tpu.ops import als

    src = synthetic.chunk_source(n_events, seed=3, chunk=1 << 20)
    with devicewatch.RssWatcher(interval_s=0.2) as w:
        td = synthetic.training_data(
            n_events, seed=3, chunk=1 << 20, stream=True)
        assert td.streamed and td.n == n_events
        data = als_algorithm._ensure_layout(None, td, use_mesh=False)
        # scan kernel: the memory-lean Gram accumulator (the hybrid's
        # dense D matrix is O(users x 2K) — deliberately avoided at
        # soak scale)
        U, V = als.train_explicit(data, rank=4, iterations=1, seed=1,
                                  kernel="scan")
        import jax
        jax.device_get((U[-1:], V[-1:]))
    assert np.isfinite(np.asarray(U[-1:])).all()
    measured = w.peak_pipeline - ((w.baseline_pipeline or 0)
                                  if relative else 0)
    assert measured <= budget_bytes, (
        f"streamed train peak pipeline RSS "
        f"{'growth ' if relative else ''}{measured / 2**30:.2f} "
        f"GiB exceeds the {budget_bytes / 2**30:.1f} GiB O(chunk) budget")
    return w, src


def test_streamed_smoke_pipeline_rss_bounded():
    """Tier-1-scale streamed smoke: the full stream→stage→layout→train
    pipeline runs and the peak PIPELINE host RSS growth (RSS minus live
    jax bytes, minus the shared test process's baseline —
    KNOWN_ISSUES #14) stays inside a 2 GB budget, trivially loose at
    this scale; the 1 B soak below tightens the ABSOLUTE bound against
    a dataset 3 orders of magnitude past it in a dedicated process."""
    if os.name != "posix" or not os.path.exists("/proc/self/status"):
        pytest.skip("needs /proc for RSS accounting")
    _soak(300_000, budget_bytes=2 << 30, relative=True)


@pytest.mark.slow
def test_billion_rating_soak():
    """THE ROADMAP item-6 gate: PIO_SOAK_RATINGS (default 1e9) synthetic
    ratings train to completion without OOM, peak pipeline host RSS
    <= 4 GB with default chunking. Hours on the 1-core dev container —
    slow-marked, run deliberately."""
    if not os.path.exists("/proc/self/status"):
        pytest.skip("needs /proc for RSS accounting")
    n = int(float(os.environ.get("PIO_SOAK_RATINGS", "1e9")))
    w, src = _soak(n, budget_bytes=4 << 30)
    assert src.n_chunks >= n // (1 << 20)
