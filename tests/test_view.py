"""Batch views: EventSeq/LBatchView folds + DataView columnar snapshot cache
(ref: data/.../view/{LBatchView,DataView}.scala)."""

import datetime as dt

import numpy as np
import pytest

from predictionio_tpu.data import store, view
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App


@pytest.fixture()
def app(memory_storage):
    apps = memory_storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "viewapp", None))
    memory_storage.get_events().init(app_id)
    return app_id


def t(minute):
    return dt.datetime(2021, 1, 1, 0, minute, tzinfo=dt.timezone.utc)


def ev(name, eid, props=None, minute=0, etype="user", **kw):
    return Event(event=name, entity_type=etype, entity_id=eid,
                 properties=DataMap(props or {}), event_time=t(minute), **kw)


def seed(app_id):
    store.write([
        ev("$set", "u1", {"plan": "free"}, minute=0),
        ev("$set", "u1", {"plan": "pro", "age": 30}, minute=2),
        ev("$unset", "u2", {"plan": None}, minute=3),
        ev("$set", "u2", {"plan": "free"}, minute=1),
        ev("$delete", "u3", minute=5),
        ev("$set", "u3", {"plan": "pro"}, minute=4),
        ev("buy", "u1", {"price": 9.5}, minute=6,
           target_entity_type="item", target_entity_id="i1"),
        ev("buy", "u2", {"price": 3.0}, minute=7,
           target_entity_type="item", target_entity_id="i2"),
        ev("$set", "cart1", {"open": True}, minute=8, etype="cart"),
    ], app_id)


class TestEventSeq:
    def test_filter_semantics(self, memory_storage, app):
        seed(app)
        lbv = view.LBatchView(app, storage=memory_storage)
        seq = lbv.events
        assert len(seq) == 9
        # event filter
        assert {e.entity_id for e in seq.filter(event="buy")} == {"u1", "u2"}
        # start_time strictly-after, until_time strictly-before
        # (ViewPredicates semantics, LBatchView.scala:39-52)
        win = seq.filter(start_time=t(6), until_time=t(8))
        assert [e.entity_id for e in win] == ["u2"]
        # entity_type
        assert [e.entity_id for e in seq.filter(entity_type="cart")] == \
            ["cart1"]
        # custom predicate composes
        pricy = seq.filter(event="buy",
                           predicate=lambda e: e.properties.get("price") > 5)
        assert [e.entity_id for e in pricy] == ["u1"]

    def test_aggregate_by_entity_ordered(self, memory_storage, app):
        seed(app)
        seq = view.LBatchView(app, storage=memory_storage).events.filter(
            event="buy")
        total = seq.aggregate_by_entity_ordered(
            0.0, lambda acc, e: acc + e.properties.get("price"))
        assert total == {"u1": 9.5, "u2": 3.0}

    def test_fold_respects_event_time_not_insert_order(
            self, memory_storage, app):
        seed(app)  # u2's $set (minute 1) was written AFTER its $unset (min 3)
        lbv = view.LBatchView(app, storage=memory_storage)
        props = lbv.aggregate_properties("user")
        assert props["u1"].get("plan") == "pro" and props["u1"].get("age") == 30
        assert not props["u2"].contains("plan")     # unset won (later time)
        assert "u3" not in props                    # $delete (minute 5) last
        assert "cart1" not in props                 # wrong entityType

    def test_window_scopes_view(self, memory_storage, app):
        seed(app)
        lbv = view.LBatchView(app, until_time=t(2), storage=memory_storage)
        props = lbv.aggregate_properties("user")
        assert props["u1"].get("plan") == "free"    # pro $set at minute 2 cut


class TestDataViewCreate:
    @staticmethod
    def conv(e):
        if e.event != "buy":
            return None
        return {"user": e.entity_id, "item": e.target_entity_id,
                "price": float(e.properties.get("price"))}

    def test_columnar_snapshot(self, memory_storage, app, tmp_path):
        seed(app)
        cols = view.create("viewapp", self.conv, name="buys",
                           base_dir=str(tmp_path), storage=memory_storage)
        assert sorted(cols) == ["item", "price", "user"]
        assert cols["price"].dtype == np.float64
        assert list(cols["user"]) == ["u1", "u2"]
        np.testing.assert_allclose(cols["price"], [9.5, 3.0])

    def test_cache_hit_skips_store(self, memory_storage, app, tmp_path):
        seed(app)
        win = dict(start_time=t(0), until_time=t(30))
        first = view.create("viewapp", self.conv, name="buys",
                            base_dir=str(tmp_path), storage=memory_storage,
                            **win)
        assert len(first["user"]) == 2
        # new event inside the window; same key => cached copy returned
        store.write([ev("buy", "u9", {"price": 1.0}, minute=9,
                        target_entity_type="item", target_entity_id="i9")],
                    app)
        again = view.create("viewapp", self.conv, name="buys",
                            base_dir=str(tmp_path), storage=memory_storage,
                            **win)
        assert list(again["user"]) == ["u1", "u2"]
        # bumping version invalidates (DataView.scala:53-54 contract)
        fresh = view.create("viewapp", self.conv, name="buys", version="v2",
                            base_dir=str(tmp_path), storage=memory_storage,
                            **win)
        assert list(fresh["user"]) == ["u1", "u2", "u9"]

    def test_channel_gets_own_cache_key(self, memory_storage, app, tmp_path):
        from predictionio_tpu.data.storage import Channel
        seed(app)
        cid = memory_storage.get_meta_data_channels().insert(
            Channel(0, "mobile", app))
        memory_storage.get_events().init(app, cid)
        store.write([ev("buy", "m1", {"price": 2.0}, minute=1,
                        target_entity_type="item", target_entity_id="i1")],
                    app, cid)
        win = dict(start_time=t(0), until_time=t(30))
        default = view.create("viewapp", self.conv, name="buys",
                              base_dir=str(tmp_path),
                              storage=memory_storage, **win)
        mobile = view.create("viewapp", self.conv, name="buys",
                             channel_name="mobile", base_dir=str(tmp_path),
                             storage=memory_storage, **win)
        assert list(default["user"]) == ["u1", "u2"]
        assert list(mobile["user"]) == ["m1"]   # not the default's cache

    def test_non_scalar_column_rejected_before_cache_write(
            self, memory_storage, app, tmp_path):
        seed(app)
        def bad(e):
            if e.event != "buy":
                return None
            return {"user": e.entity_id, "tags": ["a", "b"]}
        with pytest.raises(ValueError, match="non-scalar"):
            view.create("viewapp", bad, name="tags",
                        base_dir=str(tmp_path), storage=memory_storage)
        assert not list(tmp_path.glob("*.npz"))   # nothing poisoned

    def test_inconsistent_rows_rejected(self, memory_storage, app, tmp_path):
        seed(app)
        def bad(e):
            if e.event != "buy":
                return None
            return {"user": e.entity_id} if e.entity_id == "u1" else \
                {"other": 1}
        with pytest.raises(ValueError, match="inconsistent"):
            view.create("viewapp", bad, name="bad",
                        base_dir=str(tmp_path), storage=memory_storage)


def test_out_of_range_int_rejected_before_cache_write(
        memory_storage, app, tmp_path):
    seed(app)
    def huge(e):
        if e.event != "buy":
            return None
        return {"id": 2 ** 64}
    with pytest.raises(ValueError, match="int64"):
        view.create("viewapp", huge, name="huge",
                    base_dir=str(tmp_path), storage=memory_storage)
    assert not list(tmp_path.glob("*.npz"))
