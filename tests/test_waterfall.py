"""Latency-waterfall tests (common/waterfall.py).

Acceptance surface: with sampling enabled, a served request's stage
breakdown is reconstructable END TO END from `/debug/slow.json` plus
the `/metrics` exemplars (the bucket's trace id joins the two); with
`PIO_WATERFALL=0` (the default) responses and the metrics series are
byte-identical to the pre-waterfall code.
"""

import json
import re
import urllib.request

import pytest

from predictionio_tpu.common import telemetry, tracing, waterfall
from predictionio_tpu.controller import EngineParams
from predictionio_tpu.data.api.http import serve_background
from predictionio_tpu.data.datamap import DataMap
from predictionio_tpu.data.event import Event
from predictionio_tpu.data.storage import App
from predictionio_tpu.models.recommendation import (
    ALSAlgorithmParams, DataSourceParams, RecommendationEngine,
)
from predictionio_tpu.workflow import WorkflowContext, run_train
from predictionio_tpu.workflow.create_server import QueryAPI, ServerConfig


@pytest.fixture(autouse=True)
def _clean_waterfall():
    waterfall.set_enabled(None)
    waterfall.clear()
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()
    yield
    waterfall.set_enabled(None)
    waterfall.clear()
    telemetry.set_enabled(None)
    tracing.set_enabled(None)
    tracing.clear()


def _trained_query_api(storage, **config):
    """Seed, train, and deploy a small recommendation engine (the
    test_telemetry recipe)."""
    apps = storage.get_meta_data_apps()
    app_id = apps.insert(App(0, "WfApp", None))
    storage.get_events().init(app_id)
    import datetime as dt
    events = []
    for u in range(8):
        for i in range(6):
            events.append(Event(
                event="rate", entity_type="user", entity_id=f"u{u}",
                target_entity_type="item", target_entity_id=f"i{i}",
                properties=DataMap(
                    {"rating": 5.0 if (u % 2) == (i % 2) else 1.0}),
                event_time=dt.datetime(2021, 1, 1, 0, (u * 6 + i) % 60,
                                       tzinfo=dt.timezone.utc)))
    storage.get_events().insert_batch(events, app_id)
    engine = RecommendationEngine()
    ep = EngineParams(
        data_source_params=DataSourceParams(appName="WfApp"),
        algorithm_params_list=(
            ("als", ALSAlgorithmParams(rank=4, numIterations=3,
                                       lambda_=0.05, seed=3)),))
    run_train(WorkflowContext(storage=storage), engine, ep,
              engine_factory="waterfall-test",
              params_json={
                  "datasource": {"params": {"appName": "WfApp"}},
                  "algorithms": [{"name": "als", "params": {
                      "rank": 4, "numIterations": 3, "lambda": 0.05,
                      "seed": 3}}]})
    return QueryAPI(storage=storage, engine=engine,
                    config=ServerConfig(**config))


# ---------------------------------------------------------------------------
# unit: record/stage/ring mechanics
# ---------------------------------------------------------------------------

def test_begin_returns_none_when_disabled():
    waterfall.set_enabled(False)
    assert waterfall.begin("batched") is None
    # and stage() is a pure passthrough with nothing active
    with waterfall.stage("dispatch"):
        pass
    assert waterfall.slow_snapshot()["requests"] == []


def test_stages_accumulate_and_ring_keeps_slowest(monkeypatch):
    waterfall.set_enabled(True)
    monkeypatch.setenv("PIO_SLOW_RING", "3")
    recs = []
    for i in range(6):
        rec = waterfall.begin("inline")
        assert rec is not None
        with waterfall.activate((rec,)):
            with waterfall.stage("dispatch"):
                pass
        rec.note("i", i)
        # deterministic totals: slower for larger i
        rec.total_s = 0.001 * (i + 1)
        waterfall._ring.add(rec)
        recs.append(rec)
    snap = waterfall.slow_snapshot()
    assert snap["capacity"] == 3
    totals = [r["totalMs"] for r in snap["requests"]]
    assert totals == sorted(totals, reverse=True)
    assert totals[0] == pytest.approx(6.0)
    assert {r["details"]["i"] for r in snap["requests"]} == {3, 4, 5}
    # stage breakdown + trace id present on every entry
    for r in snap["requests"]:
        assert "dispatch" in r["stages"]
        assert r["traceId"]


def test_ring_shrink_evicts_fastest_and_respects_new_cap(monkeypatch):
    """When PIO_SLOW_RING shrinks between requests, the ring must drop
    its FASTEST entries (never by insertion order) and settle at the
    new cap exactly."""
    waterfall.set_enabled(True)
    monkeypatch.setenv("PIO_SLOW_RING", "6")
    for i in range(6):
        rec = waterfall.begin("inline")
        # insertion order deliberately != slowness order
        rec.total_s = 0.001 * ((i * 3) % 7 + 1)
        waterfall._ring.add(rec)
    monkeypatch.setenv("PIO_SLOW_RING", "2")
    rec = waterfall.begin("inline")
    rec.total_s = 0.0045
    waterfall._ring.add(rec)
    snap = waterfall.slow_snapshot()
    totals = [r["totalMs"] for r in snap["requests"]]
    # exactly the new cap, holding the two slowest seen overall
    assert len(totals) == 2
    assert totals == sorted(totals, reverse=True)
    assert min(totals) >= 4.5
    # a fast request arriving now must not displace anything
    rec = waterfall.begin("inline")
    rec.total_s = 0.0001
    waterfall._ring.add(rec)
    assert [r["totalMs"] for r in waterfall.slow_snapshot()["requests"]] \
        == totals


def test_sampling_every_nth(monkeypatch):
    waterfall.set_enabled(True)
    monkeypatch.setenv("PIO_WATERFALL_SAMPLE", "4")
    sampled = sum(1 for _ in range(40)
                  if waterfall.begin("inline") is not None)
    assert sampled == 10


def test_record_adopts_active_trace_id():
    waterfall.set_enabled(True)
    ctx = tracing.new_context()
    with tracing.activate(ctx):
        rec = waterfall.begin("batched")
    assert rec.trace_id == ctx.trace_id


def test_histogram_exemplars_in_openmetrics_exposition():
    reg = telemetry.MetricsRegistry()
    h = reg.histogram("x_seconds", "t", labelnames=("stage",),
                      buckets=(0.001, 0.1)).labels(stage="pad")
    h.observe(0.0005, exemplar="trace-a")
    h.observe(5.0, exemplar="trace-b")
    h.observe(0.0004)   # no exemplar: must not clobber trace-a
    text = reg.exposition(openmetrics=True)
    a = re.search(r'x_seconds_bucket\{stage="pad",le="0\.001"\} 2 '
                  r'# \{trace_id="trace-a"\} 0\.0005', text)
    b = re.search(r'x_seconds_bucket\{stage="pad",le="\+Inf"\} 3 '
                  r'# \{trace_id="trace-b"\} 5', text)
    assert a and b, text
    # sum/count lines stay exemplar-free
    assert re.search(r"x_seconds_count\{stage=\"pad\"\} 3\s*$", text,
                     re.M)
    # OpenMetrics exposition terminates with # EOF
    assert text.endswith("# EOF\n")


def test_classic_exposition_never_carries_exemplars():
    """Exemplars are OpenMetrics-only syntax: the classic 0.0.4 parser
    reads the token after the value as a timestamp and fails the line,
    so the default exposition must stay exemplar-free even after one
    was recorded."""
    reg = telemetry.MetricsRegistry()
    reg.histogram("x_seconds", "t", labelnames=("stage",),
                  buckets=(0.001,)).labels(stage="pad").observe(
        0.0005, exemplar="trace-a")
    # a counter family rides along to pin classic TYPE naming
    reg.counter("x_events_total", "t").child().inc()
    text = reg.exposition()
    assert " # {" not in text, text
    assert "# EOF" not in text
    assert "# TYPE x_events_total counter" in text
    # openmetrics mode strips the counter family's _total suffix in
    # the meta lines (sample lines keep it)
    om = reg.exposition(openmetrics=True)
    assert "# TYPE x_events counter" in om
    assert re.search(r"^x_events_total 1$", om, re.M), om


def test_metrics_route_negotiates_openmetrics():
    """/metrics answers classic 0.0.4 by default and OpenMetrics (with
    the matching Content-Type) only when the Accept header asks."""
    st, body, hdrs = telemetry.handle_route("GET", "/metrics")
    assert st == 200
    assert hdrs["Content-Type"].startswith("text/plain")
    assert "# EOF" not in body
    st, body, hdrs = telemetry.handle_route(
        "GET", "/metrics",
        accept="application/openmetrics-text;version=1.0.0;q=0.75,"
               "text/plain;version=0.0.4;q=0.5")
    assert st == 200
    assert hdrs["Content-Type"].startswith("application/openmetrics-text")
    assert body.endswith("# EOF\n")


def test_doctor_parser_strips_exemplars():
    from predictionio_tpu.tools import doctor
    text = ('pio_serve_stage_seconds_bucket{stage="pad",le="0.001"} 2 '
            '# {trace_id="abcd"} 0.0005\n'
            'pio_serve_stage_seconds_count{stage="pad"} 2\n')
    samples = doctor.parse_metrics(text)
    assert samples["pio_serve_stage_seconds_bucket"][0][1] == 2
    assert samples["pio_serve_stage_seconds_count"][0][1] == 2


# ---------------------------------------------------------------------------
# e2e: slow.json + exemplars reconstruct a served request (acceptance)
# ---------------------------------------------------------------------------

def test_stage_breakdown_reconstructable_end_to_end(memory_storage,
                                                    monkeypatch):
    """Serve real HTTP traffic with sampling on; the slowest request's
    stage breakdown must be reconstructable from /debug/slow.json and
    its trace id must appear among the /metrics stage exemplars."""
    # force the device serving path so the pad/execute drill-down
    # stages are exercised (prepare_serving would otherwise pick
    # whichever layout happens to probe faster on this host)
    monkeypatch.setenv("PIO_SERVE_DEVICE_MS", "10000")
    waterfall.set_enabled(True)
    api = _trained_query_api(memory_storage, batching="on")
    server, port = serve_background(api, "127.0.0.1", 0)
    try:
        base = f"http://127.0.0.1:{port}"
        for q in range(6):
            body = json.dumps({"user": f"u{q % 8}", "num": 4}).encode()
            req = urllib.request.Request(
                f"{base}/queries.json", data=body,
                headers={"Content-Type": "application/json"},
                method="POST")
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
        with urllib.request.urlopen(f"{base}/debug/slow.json",
                                    timeout=10) as r:
            slow = json.loads(r.read().decode())
        assert slow["enabled"] is True
        reqs = slow["requests"]
        assert reqs, "no sampled requests in the slow ring"
        top = reqs[0]
        stages = top["stages"]
        # the batched path's full decomposition, including the
        # algorithm-level pad/execute drill-down inside dispatch
        assert {"admission", "supplement", "dispatch", "merge",
                "serialize"} <= set(stages)
        assert {"pad", "execute"} <= set(stages)
        # top-level stages sum to (at most) the request total — the
        # breakdown genuinely reconstructs where the time went
        top_level = sum(stages[s] for s in
                        ("admission", "supplement", "dispatch", "merge",
                         "serialize"))
        assert 0 < top_level <= top["totalMs"] + 0.5
        # the drill-down stays inside its parent
        assert stages["pad"] + stages["execute"] <= \
            stages["dispatch"] + 0.5
        # the flush's padding bucket rode along as the diagnosis detail
        assert top["details"]["bucket"] >= 1
        # exemplar join: some stage bucket on /metrics names a trace id
        # from the slow ring — alarm -> exemplar -> slow.json in one
        # hop. Exemplars ride the OpenMetrics exposition only, so the
        # scrape negotiates it via Accept...
        om_req = urllib.request.Request(
            f"{base}/metrics",
            headers={"Accept": "application/openmetrics-text"})
        with urllib.request.urlopen(om_req, timeout=10) as r:
            assert r.headers["Content-Type"].startswith(
                "application/openmetrics-text")
            text = r.read().decode()
        # ...while a classic scraper (no Accept) stays exemplar-free —
        # its 0.0.4 parser would read the exemplar as a timestamp
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as r:
            assert r.headers["Content-Type"].startswith("text/plain")
            assert " # {" not in r.read().decode()
        exemplar_ids = set(re.findall(
            r'pio_serve_stage_seconds_bucket\{[^}]*\}[^#\n]*'
            r'# \{trace_id="([^"]+)"\}', text))
        assert exemplar_ids, "no stage exemplars in the exposition"
        ring_ids = {r_["traceId"] for r_ in reqs}
        assert exemplar_ids & ring_ids
        # and the query server serves every shared debug surface
        for path in telemetry.DEBUG_PATHS:
            with urllib.request.urlopen(f"{base}{path}",
                                        timeout=10) as r:
                assert r.status == 200
    finally:
        server.shutdown()
        api.close()


def test_inline_path_records_stages(memory_storage):
    waterfall.set_enabled(True)
    api = _trained_query_api(memory_storage, batching="off")
    try:
        st, _ = api.handle("POST", "/queries.json", body=json.dumps(
            {"user": "u1", "num": 2}).encode())
        assert st == 200
        reqs = waterfall.slow_snapshot()["requests"]
        assert reqs and reqs[0]["mode"] == "inline"
        # inline: no batcher, so no admission stage; the rest present
        assert {"supplement", "dispatch", "merge", "serialize"} <= \
            set(reqs[0]["stages"])
        assert "admission" not in reqs[0]["stages"]
    finally:
        api.close()


def test_wire_parity_with_waterfall_off(memory_storage):
    """PIO_WATERFALL unset (default): responses byte-identical whether
    the request ran before or after a waterfall-on era, no
    pio_serve_stage series, and /debug/slow.json reports disabled."""
    api = _trained_query_api(memory_storage, batching="on")
    try:
        body = json.dumps({"user": "u1", "num": 4}).encode()
        waterfall.set_enabled(False)
        st_off, off = api.handle("POST", "/queries.json", body=body)
        waterfall.set_enabled(True)
        st_on, on = api.handle("POST", "/queries.json", body=body)
        waterfall.set_enabled(False)
        st_off2, off2 = api.handle("POST", "/queries.json", body=body)
        assert (st_off, json.dumps(off)) == (st_on, json.dumps(on))
        assert (st_off, json.dumps(off)) == (st_off2, json.dumps(off2))
        st, slow = api.handle("GET", "/debug/slow.json")
        assert st == 200 and slow["enabled"] is False
    finally:
        api.close()


def test_slow_json_limit_validation(memory_storage):
    api = _trained_query_api(memory_storage, batching="off")
    try:
        st, payload = api.handle("GET", "/debug/slow.json",
                                 query={"limit": "bogus"})
        assert st == 400 and "limit" in payload["message"]
        st, payload = api.handle("GET", "/debug/slow.json",
                                 query={"limit": "2"})
        assert st == 200
    finally:
        api.close()
