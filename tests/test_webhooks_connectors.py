"""Production webhook connectors end-to-end (SegmentIOConnector.scala /
MailChimpConnector.scala parity): every message type of both
default-registered connectors converts over the fixture payloads, the
EventAPI ingests them channel-scoped at the wire (201), and malformed
payloads answer 400 — never 500."""

import json
import urllib.parse

import pytest

from predictionio_tpu.data.api import EventAPI, EventServerConfig
from predictionio_tpu.data.storage import AccessKey, App, Channel
from predictionio_tpu.data.webhooks import (
    ConnectorException, default_form_connectors, default_json_connectors,
    to_event,
)
from predictionio_tpu.data.webhooks.examples import (
    MAILCHIMP_EXAMPLES, SEGMENTIO_EXAMPLES,
)
from predictionio_tpu.data.webhooks.mailchimp import (
    MailChimpConnector, parse_mailchimp_datetime,
)
from predictionio_tpu.data.webhooks.segmentio import SegmentIOConnector


# ---------------------------------------------------------------------------
# segment.io: all six message types + malformed payloads
# ---------------------------------------------------------------------------

class TestSegmentIO:
    @pytest.mark.parametrize("typ", sorted(SEGMENTIO_EXAMPLES))
    def test_every_type_converts(self, typ):
        payload = SEGMENTIO_EXAMPLES[typ]
        ev = to_event(SegmentIOConnector(), payload)
        assert ev.event == typ
        assert ev.entity_type == "user"
        assert ev.entity_id in (payload.get("user_id"),
                                payload.get("anonymous_id"))
        assert ev.event_time.year == 2015
        if payload.get("context") is not None:
            assert ev.properties.get("context")["ip"] == "8.8.8.8"

    def test_track_carries_event_name(self):
        j = SegmentIOConnector().to_event_json(SEGMENTIO_EXAMPLES["track"])
        assert j["properties"]["event"] == "Registered"
        assert j["properties"]["properties"]["plan"] == "Pro Annual"

    def test_missing_version(self):
        bad = {k: v for k, v in SEGMENTIO_EXAMPLES["track"].items()
               if k != "version"}
        with pytest.raises(ConnectorException, match="API version"):
            SegmentIOConnector().to_event_json(bad)

    def test_unknown_type(self):
        with pytest.raises(ConnectorException, match="unknown type"):
            SegmentIOConnector().to_event_json(
                {"version": 2, "type": "purchase", "user_id": "u"})

    def test_missing_user(self):
        bad = {k: v for k, v in SEGMENTIO_EXAMPLES["identify"].items()
               if k != "user_id"}
        with pytest.raises(ConnectorException, match="anonymousId"):
            SegmentIOConnector().to_event_json(bad)

    def test_missing_required_field(self):
        # track without its event name; group without group_id
        bad = {k: v for k, v in SEGMENTIO_EXAMPLES["track"].items()
               if k != "event"}
        with pytest.raises(ConnectorException, match="missing event"):
            SegmentIOConnector().to_event_json(bad)
        bad = {k: v for k, v in SEGMENTIO_EXAMPLES["group"].items()
               if k != "group_id"}
        with pytest.raises(ConnectorException, match="missing group_id"):
            SegmentIOConnector().to_event_json(bad)


# ---------------------------------------------------------------------------
# MailChimp: all six callback types + malformed payloads
# ---------------------------------------------------------------------------

class TestMailChimp:
    @pytest.mark.parametrize("typ", sorted(MAILCHIMP_EXAMPLES))
    def test_every_type_converts(self, typ):
        ev = to_event(MailChimpConnector(), MAILCHIMP_EXAMPLES[typ])
        assert ev.event == typ
        assert ev.event_time.year == 2009

    def test_subscribe_shape(self):
        j = MailChimpConnector().to_event_json(
            MAILCHIMP_EXAMPLES["subscribe"])
        assert j["entityType"] == "user" and j["entityId"] == "8a25ff1d98"
        assert j["targetEntityType"] == "list"
        assert j["targetEntityId"] == "a6b5da1054"
        assert j["properties"]["merges"]["FNAME"] == "MailChimp"
        assert j["properties"]["merges"]["INTERESTS"] == "Group1,Group2"

    def test_datetime_parse(self):
        assert (parse_mailchimp_datetime("2009-03-26 21:35:57")
                == "2009-03-26T21:35:57Z")
        with pytest.raises(ConnectorException, match="fired_at"):
            parse_mailchimp_datetime("26/03/2009")

    def test_missing_and_unknown_type(self):
        with pytest.raises(ConnectorException, match="'type' is required"):
            MailChimpConnector().to_event_json({"fired_at": "x"})
        with pytest.raises(ConnectorException, match="unknown MailChimp"):
            MailChimpConnector().to_event_json({"type": "pong"})

    def test_missing_required_field(self):
        bad = {k: v for k, v in MAILCHIMP_EXAMPLES["subscribe"].items()
               if k != "data[email]"}
        with pytest.raises(ConnectorException, match="data\\[email\\]"):
            MailChimpConnector().to_event_json(bad)

    def test_default_registries(self):
        assert isinstance(default_json_connectors()["segmentio"],
                          SegmentIOConnector)
        assert isinstance(default_form_connectors()["mailchimp"],
                          MailChimpConnector)


# ---------------------------------------------------------------------------
# wire level: channel-scoped ingestion through the EventAPI
# ---------------------------------------------------------------------------

@pytest.fixture()
def api(memory_storage):
    app_id = memory_storage.get_meta_data_apps().insert(
        App(0, "HookApp", None))
    memory_storage.get_events().init(app_id)
    memory_storage.get_meta_data_access_keys().insert(
        AccessKey("hook-key", app_id, ()))
    cid = memory_storage.get_meta_data_channels().insert(
        Channel(0, "mobile", app_id))
    memory_storage.get_events().init(app_id, cid)
    a = EventAPI(storage=memory_storage, config=EventServerConfig())
    a.app_id = app_id
    return a


class TestWebhookWire:
    def test_segmentio_channel_scoped_201(self, api):
        q = {"accessKey": "hook-key", "channel": "mobile"}
        status, body = api.handle(
            "POST", "/webhooks/segmentio.json", q,
            json.dumps(SEGMENTIO_EXAMPLES["track"]).encode())
        assert status == 201 and body["eventId"]
        # visible on that channel...
        status, events = api.handle("GET", "/events.json", q)
        assert status == 200 and events[0]["event"] == "track"
        # ...and NOT on the default channel (channel separation)
        status, _ = api.handle("GET", "/events.json",
                               {"accessKey": "hook-key"})
        assert status == 404

    def test_mailchimp_form_201(self, api):
        body = urllib.parse.urlencode(
            MAILCHIMP_EXAMPLES["subscribe"]).encode()
        status, out = api.handle(
            "POST", "/webhooks/mailchimp.form",
            {"accessKey": "hook-key"}, body)
        assert status == 201 and out["eventId"]
        status, events = api.handle("GET", "/events.json",
                                    {"accessKey": "hook-key"})
        assert status == 200 and events[0]["event"] == "subscribe"
        assert events[0]["properties"]["merges"]["LNAME"] == "API"

    def test_malformed_payload_400(self, api):
        q = {"accessKey": "hook-key"}
        status, body = api.handle(
            "POST", "/webhooks/segmentio.json", q, b"{not json")
        assert status == 400
        status, body = api.handle(
            "POST", "/webhooks/segmentio.json", q,
            json.dumps({"type": "track"}).encode())   # no version
        assert status == 400 and "version" in body["message"]
        status, body = api.handle(
            "POST", "/webhooks/mailchimp.form", q,
            urllib.parse.urlencode({"type": "subscribe"}).encode())
        assert status == 400 and "required" in body["message"]

    def test_auth_and_unknown_connector(self, api):
        status, body = api.handle(
            "POST", "/webhooks/segmentio.json", {"accessKey": "wrong"},
            json.dumps(SEGMENTIO_EXAMPLES["track"]).encode())
        assert status == 401
        status, body = api.handle(
            "POST", "/webhooks/segmentio.json",
            {"accessKey": "hook-key", "channel": "nope"},
            json.dumps(SEGMENTIO_EXAMPLES["track"]).encode())
        assert status == 401 and "Invalid channel" in body["message"]
        status, body = api.handle(
            "POST", "/webhooks/zapier.json", {"accessKey": "hook-key"},
            b"{}")
        assert status == 404 and "not supported" in body["message"]

    def test_presence_checks(self, api):
        q = {"accessKey": "hook-key"}
        assert api.handle("GET", "/webhooks/segmentio.json", q)[0] == 200
        assert api.handle("GET", "/webhooks/mailchimp.form", q)[0] == 200
        assert api.handle("GET", "/webhooks/zapier.json", q)[0] == 404
        assert api.handle("GET", "/webhooks/segmentio.form", q)[0] == 404
