"""SPI-demo connector pair (ExampleJsonConnector.scala /
ExampleFormConnector.scala parity): both payload types of each variant
convert to valid Events; malformed payloads raise ConnectorException."""

import pytest

from predictionio_tpu.data.webhooks import ConnectorException, to_event
from predictionio_tpu.data.webhooks.examples import (
    ExampleFormConnector, ExampleJsonConnector,
)


def test_json_user_action_roundtrip():
    ev = to_event(ExampleJsonConnector(), {
        "type": "userAction", "userId": "as34smg4", "event": "do_something",
        "context": {"ip": "24.5.68.47", "prop1": 2.345, "prop2": "value1"},
        "anotherProperty1": 100, "anotherProperty2": "optional1",
        "timestamp": "2015-01-02T00:30:12.984Z"})
    assert ev.event == "do_something"
    assert ev.entity_type == "user" and ev.entity_id == "as34smg4"
    assert ev.properties.get("anotherProperty1") == 100
    assert ev.properties.get("context")["ip"] == "24.5.68.47"
    assert ev.event_time.year == 2015


def test_json_user_action_item_roundtrip():
    ev = to_event(ExampleJsonConnector(), {
        "type": "userActionItem", "userId": "as34smg4",
        "event": "do_something_on", "itemId": "kfjd312bc",
        "context": {"ip": "1.23.4.56", "prop1": 2.345, "prop2": "value1"},
        "anotherPropertyA": 4.567, "anotherPropertyB": False,
        "timestamp": "2015-01-15T04:20:23.567Z"})
    assert ev.target_entity_type == "item"
    assert ev.target_entity_id == "kfjd312bc"
    assert ev.properties.get("anotherPropertyA") == pytest.approx(4.567)


def test_json_unknown_and_missing_type():
    with pytest.raises(ConnectorException, match="unknown type"):
        ExampleJsonConnector().to_event_json({"type": "nope"})
    with pytest.raises(ConnectorException, match="required"):
        ExampleJsonConnector().to_event_json({"userId": "x"})


def test_form_user_action_optional_context():
    c = ExampleFormConnector()
    # without any context[...] key the context property is absent
    j = c.to_event_json({
        "type": "userAction", "userId": "u1", "event": "do_something",
        "anotherProperty1": "100",
        "timestamp": "2015-01-02T00:30:12.984Z"})
    assert "context" not in j["properties"]
    assert j["properties"]["anotherProperty1"] == 100
    # bracketed context keys parse into a nested object with typed values
    j = c.to_event_json({
        "type": "userAction", "userId": "u1", "event": "do_something",
        "context[ip]": "24.5.68.47", "context[prop1]": "2.345",
        "anotherProperty1": "100",
        "timestamp": "2015-01-02T00:30:12.984Z"})
    assert j["properties"]["context"] == {"ip": "24.5.68.47", "prop1": 2.345}


def test_form_user_action_item_requires_context():
    c = ExampleFormConnector()
    with pytest.raises(ConnectorException, match="context"):
        c.to_event_json({
            "type": "userActionItem", "userId": "u1", "event": "e",
            "itemId": "i1", "timestamp": "2015-01-15T04:20:23.567Z"})
    ev = to_event(c, {
        "type": "userActionItem", "userId": "u1", "event": "view",
        "itemId": "i1", "context[ip]": "1.2.3.4", "context[prop1]": "1.5",
        "anotherPropertyB": "true",
        "timestamp": "2015-01-15T04:20:23.567Z"})
    assert ev.properties.get("anotherPropertyB") is True
    assert ev.properties.get("context")["prop1"] == 1.5


def test_form_bad_number_is_connector_error():
    with pytest.raises(ConnectorException, match="Cannot convert"):
        ExampleFormConnector().to_event_json({
            "type": "userAction", "userId": "u1", "event": "e",
            "anotherProperty1": "not-a-number",
            "timestamp": "2015-01-02T00:30:12.984Z"})
